"""IPv6 device first-match kernel vs the exact oracle (golden semantics).

The v6 twin of test_match.py: 128-bit addresses as 4x uint32 limbs,
family-split rule tensors (pack.rules6), lexicographic range predicate.
"""

import random

import numpy as np
import pytest

from ruleset_analysis_tpu.hostside import aclparse, oracle, pack
from ruleset_analysis_tpu.hostside.syslog import ParsedLine
from ruleset_analysis_tpu.ops import match6 as match6_ops

jnp = pytest.importorskip("jax.numpy")


def cols6_from_batch(batch_np):
    b = jnp.asarray(np.ascontiguousarray(batch_np.T))
    cols = {
        "acl": b[pack.T6_ACL],
        "proto": b[pack.T6_PROTO],
        "sport": b[pack.T6_SPORT],
        "dport": b[pack.T6_DPORT],
    }
    for i in range(4):
        cols[f"src{i}"] = b[pack.T6_SRC + i]
        cols[f"dst{i}"] = b[pack.T6_DST + i]
    return cols, b[pack.T6_VALID]


CFG6 = """\
hostname fw1
access-list OUT extended permit tcp any6 host 2001:db8::5 eq 443
access-list OUT extended permit tcp any6 host 2001:db8::5 eq 80
access-list OUT extended deny tcp any6 2001:db8:dead::/48
access-list OUT extended permit ip any6 any6
access-list DMZ extended permit udp 2001:db8:9::/64 any6 eq 53
"""


def make_packed(cfg=CFG6):
    rs = aclparse.parse_asa_config(cfg, "fw1")
    return pack.pack_rulesets([rs]), rs


def tuples6(rows):
    """rows of (gid, proto, src_int, sport, dst_int, dport, valid)."""
    out = np.zeros((len(rows), pack.TUPLE6_COLS), dtype=np.uint32)
    for i, (gid, proto, src, sport, dst, dport, valid) in enumerate(rows):
        out[i] = (
            gid, proto, *pack.u128_limbs(src), sport,
            *pack.u128_limbs(dst), dport, valid,
        )
    return out


def run_keys(packed, batch_np):
    cols, _ = cols6_from_batch(batch_np)
    keys = match6_ops.match_keys6(
        cols, jnp.asarray(packed.rules6), jnp.asarray(packed.deny_key)
    )
    return [packed.key_meta[int(k)] for k in np.asarray(keys)]


def test_first_match6_golden():
    packed, _ = make_packed()
    gid = packed.acl_gid[("fw1", "OUT")]
    ip6 = aclparse.ip6_to_int
    metas = run_keys(
        packed,
        tuples6(
            [
                (gid, 6, ip6("2001:db8::9"), 999, ip6("2001:db8::5"), 443, 1),
                (gid, 6, ip6("2001:db8::9"), 999, ip6("2001:db8::5"), 80, 1),
                (gid, 6, ip6("2001:db8::9"), 80, ip6("2001:db8:dead:beef::1"), 80, 1),
                (gid, 17, ip6("::1"), 53, ip6("2001:4860::8888"), 53, 1),
            ]
        ),
    )
    assert [m.index for m in metas] == [1, 2, 3, 4]


def test_lexicographic_bounds_cross_limbs():
    """Range bounds that differ only in low limbs must compare correctly."""
    cfg = (
        "object network R\n"
        " range 2001:db8::ffff:ffff 2001:db8:0:1::2\n"
        "access-list A extended permit ip object R any6\n"
    )
    packed, rs = make_packed(cfg)
    gid = packed.acl_gid[("fw1", "A")]
    ip6 = aclparse.ip6_to_int
    inside = [ip6("2001:db8::ffff:ffff"), ip6("2001:db8:0:1::"), ip6("2001:db8:0:1::2")]
    outside = [ip6("2001:db8::ffff:fffe"), ip6("2001:db8:0:1::3"), 0, (1 << 128) - 1]
    rows = [(gid, 6, s, 1, ip6("::2"), 2, 1) for s in inside + outside]
    metas = run_keys(packed, tuples6(rows))
    assert [m.index for m in metas[: len(inside)]] == [1] * len(inside)
    assert all(m.implicit_deny for m in metas[len(inside):])


def test_implicit_deny6_and_acl_isolation():
    packed, _ = make_packed()
    gid_dmz = packed.acl_gid[("fw1", "DMZ")]
    ip6 = aclparse.ip6_to_int
    # UDP from outside DMZ's source prefix: would hit OUT's any6/any6 if
    # the ACL gid weren't checked
    metas = run_keys(
        packed, tuples6([(gid_dmz, 17, ip6("2001:db8:bad::1"), 53, ip6("::9"), 53, 1)])
    )
    assert metas[0].implicit_deny and metas[0].acl == "DMZ"


def test_scan_path6_equals_single_block():
    """Blocked rule-axis scan must equal the unblocked result."""
    rng = random.Random(7)
    lines = ["hostname fw1"]
    for i in range(40):
        net = f"2001:db8:{i:x}::/48"
        lines.append(f"access-list A extended permit tcp any6 {net} eq {1000 + i}")
    lines.append("access-list A extended deny ip any6 any6")
    packed, _ = make_packed("\n".join(lines) + "\n")
    gid = packed.acl_gid[("fw1", "A")]
    ip6 = aclparse.ip6_to_int
    rows = []
    for _ in range(200):
        i = rng.randrange(48)
        dst = ip6(f"2001:db8:{i % 44:x}::{rng.randrange(1, 1 << 16):x}")
        rows.append((gid, 6, rng.getrandbits(128), rng.randrange(1 << 16), dst,
                     1000 + rng.randrange(44), 1))
    batch = tuples6(rows)
    cols, _ = cols6_from_batch(batch)
    r6 = packed.rules6
    pad = (-len(r6)) % 8
    r6p = np.concatenate([r6, np.zeros((pad, pack.RULE6_COLS), np.uint32)])
    if pad:
        r6p[len(r6):, pack.R6_ACL] = pack.NO_ACL
    a = match6_ops.first_match_rows6(cols, jnp.asarray(r6p), rule_block=8)
    b = match6_ops.first_match_rows6(cols, jnp.asarray(r6), rule_block=len(r6) + 1)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def _rand_v6_cfg(rng, n_acls=3, rules_per_acl=12):
    """Random mixed config exercising hosts/prefixes/ranges/ports/any6."""
    lines = ["hostname fw1"]
    prefixes = [f"2001:db8:{i:x}::" for i in range(8)]

    def addr():
        r = rng.random()
        if r < 0.3:
            return f"host {rng.choice(prefixes)}{rng.randrange(1, 200):x}"
        if r < 0.7:
            return f"{rng.choice(prefixes)}/{rng.choice([44, 48, 64, 96, 126])}"
        return "any6"

    def ports():
        r = rng.random()
        if r < 0.4:
            return f" eq {rng.randrange(1, 1024)}"
        if r < 0.6:
            lo = rng.randrange(1, 60000)
            return f" range {lo} {lo + rng.randrange(1, 1000)}"
        return ""

    for a in range(n_acls):
        for _ in range(rules_per_acl):
            action = rng.choice(["permit", "deny"])
            proto = rng.choice(["tcp", "udp", "ip"])
            p = ports() if proto != "ip" else ""
            lines.append(
                f"access-list ACL{a} extended {action} {proto} {addr()} {addr()}{p}"
            )
        lines.append(f"access-list ACL{a} extended deny ip any6 any6")
    return "\n".join(lines) + "\n"


@pytest.mark.parametrize("seed", [3, 17, 99])
def test_match6_agrees_with_oracle_randomized(seed):
    rng = random.Random(seed)
    cfg = _rand_v6_cfg(rng)
    packed, rs = make_packed(cfg)
    orc = oracle.Oracle([rs])
    gids = {g: (fw, acl) for (fw, acl), g in packed.acl_gid.items()}

    prefixes = [aclparse.ip6_to_int(f"2001:db8:{i:x}::") for i in range(8)]
    rows = []
    expect = []
    for _ in range(600):
        gid = rng.randrange(packed.n_acls)
        fw, acl = gids[gid]
        proto = rng.choice([6, 17, 58, 0])
        src = rng.choice(prefixes) + rng.getrandbits(rng.choice([16, 64, 128 - 36]))
        dst = rng.choice(prefixes) + rng.getrandbits(rng.choice([16, 64]))
        sport, dport = rng.randrange(1 << 16), rng.randrange(1 << 16)
        src &= (1 << 128) - 1
        p = ParsedLine(
            firewall=fw, acl=acl, ingress_if=None, proto=proto, src=src,
            sport=sport, dst=dst, dport=dport, permitted=None, family=6,
        )
        (ek,) = orc.match_keys(p)
        expect.append(ek)
        rows.append((gid, proto, src, sport, dst, dport, 1))

    metas = run_keys(packed, tuples6(rows))
    got = [(m.firewall, m.acl, m.index) for m in metas]
    assert got == expect


def test_fold_src32_distinct_and_deterministic():
    rng = random.Random(1)
    vals = [rng.getrandbits(128) for _ in range(2000)]
    batch = tuples6([(0, 6, v, 1, 0, 2, 1) for v in vals])
    cols, _ = cols6_from_batch(batch)
    f1 = np.asarray(match6_ops.fold_src32(cols))
    f2 = np.asarray(match6_ops.fold_src32(cols))
    np.testing.assert_array_equal(f1, f2)
    # 2000 random 128-bit values: expect no 32-bit collisions (p ~ 5e-4)
    assert len(set(f1.tolist())) == len(vals)


def test_stacked6_equals_flat_keys():
    """Grouped v6 match must produce the same keys as the flat scan."""
    rng = random.Random(21)
    lines = ["hostname fw1"]
    for a in range(4):
        for i in range(6):
            lines.append(
                f"access-list ACL{a} extended permit tcp any6 "
                f"2001:db8:{a:x}{i:x}::/48 eq {1000 + i}"
            )
        lines.append(f"access-list ACL{a} extended deny ip any6 any6")
    packed, _ = make_packed("\n".join(lines) + "\n")
    g = packed.n_acls
    lane = 64
    ip6 = aclparse.ip6_to_int

    # grouped batch: [G, TUPLE6_COLS, lane], plus the equivalent flat rows
    grouped = np.zeros((g, pack.TUPLE6_COLS, lane), dtype=np.uint32)
    flat_rows = []
    for gid in range(g):
        for j in range(lane):
            a = gid
            i = rng.randrange(8)
            dst = ip6(f"2001:db8:{a:x}{i % 6:x}::{rng.randrange(1, 999):x}")
            row = (gid, 6, rng.getrandbits(128), rng.randrange(1 << 16),
                   dst, 1000 + rng.randrange(7), 1)
            grouped[gid, :, j] = (
                row[0], row[1], *pack.u128_limbs(row[2]), row[3],
                *pack.u128_limbs(row[4]), row[5], row[6],
            )
            flat_rows.append(row)

    gb = jnp.asarray(grouped)
    cols_g = {
        "acl": gb[:, pack.T6_ACL, :],
        "proto": gb[:, pack.T6_PROTO, :],
        "sport": gb[:, pack.T6_SPORT, :],
        "dport": gb[:, pack.T6_DPORT, :],
    }
    for i in range(4):
        cols_g[f"src{i}"] = gb[:, pack.T6_SRC + i, :]
        cols_g[f"dst{i}"] = gb[:, pack.T6_DST + i, :]
    rules3d = jnp.asarray(pack.stack_rules6(packed))
    deny = jnp.asarray(packed.deny_key)
    keys_stacked = np.asarray(
        match6_ops.match_keys6_stacked(cols_g, rules3d, deny)
    ).reshape(-1)

    flat_batch = tuples6(flat_rows)
    cols_f, _ = cols6_from_batch(flat_batch)
    keys_flat = np.asarray(
        match6_ops.match_keys6(cols_f, jnp.asarray(packed.rules6), deny)
    )
    np.testing.assert_array_equal(keys_stacked, keys_flat)
